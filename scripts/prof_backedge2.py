"""Calibration part 2: marginal kernel dispatch in long scans, dynamic
row addressing inside Pallas, and MXU one-hot gather.

E. scan-of-pallas-kernels at large R: marginal us/kernel (clean).
F. scan of XLA fused elementwise step at large R: marginal us/step.
G. in-kernel fori doing a *dynamic row* load+store on a [4096, 128]
   ref per iteration (the scalar-serialization primitive).
H. in-kernel blocked one-hot MXU gather: 4096 rows from [4096, 8pad128]
   vs the XLA gather of the same.
I. XLA scatter-min + gather pair at deep-window index sizes.
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def sync(x):
    return float(np.asarray(jax.device_get(x)).ravel()[0])


def timeit(fn, *args, reps=5):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def marginal(fn, Rs, label):
    prev = None
    for R in Rs:
        t = timeit(fn, R) if not isinstance(R, tuple) else timeit(fn, *R)
        r = R if not isinstance(R, tuple) else R[0]
        d = "" if prev is None else (
            f"  marginal: {(t - prev[1]) / (r - prev[0]) * 1e6:.1f} us/iter")
        print(f"  {label} R={r:6d}: {t*1e3:8.2f} ms{d}")
        prev = (r, t)


shape = jax.ShapeDtypeStruct((8, 1024), jnp.int32)


def one(x_ref, o_ref):
    o_ref[...] = x_ref[...] * jnp.int32(3) + jnp.int32(1) ^ (x_ref[...] >> 7)


@functools.partial(jax.jit, static_argnums=1)
def scan_pallas(x, R):
    def body(c, _):
        return pl.pallas_call(one, out_shape=shape)(c), None
    out, _ = jax.lax.scan(body, x, None, length=R)
    return out


@functools.partial(jax.jit, static_argnums=1)
def scan_xla(x, R):
    def body(c, _):
        return c * jnp.int32(3) + jnp.int32(1) ^ (c >> 7), None
    out, _ = jax.lax.scan(body, x, None, length=R)
    return out


def kern_dynrow(R, x_ref, o_ref):
    def body(i, acc):
        r = (i * jnp.int32(-1640531527)) % jnp.int32(4096)
        row = x_ref[pl.ds(r, 1), :]
        o_ref[pl.ds(r, 1), :] = row + acc
        return acc + jnp.int32(1)
    acc = jax.lax.fori_loop(0, R, body, jnp.int32(0))
    o_ref[pl.ds(0, 1), :] = o_ref[pl.ds(0, 1), :] + acc


@functools.partial(jax.jit, static_argnums=1)
def dynrow(x, R):
    return pl.pallas_call(functools.partial(kern_dynrow, R),
                          out_shape=jax.ShapeDtypeStruct((4096, 128),
                                                         jnp.int32),
                          input_output_aliases={0: 0})(x)


BLK = 512


def kern_onehot(x_ref, idx_ref, o_ref):
    # gather rows idx[j] (j in [0,4096)) from x [4096, 128] via blocked
    # one-hot matmul on the MXU
    idx = idx_ref[...]                                   # [8, 512] int32
    idxf = idx.reshape(4096)
    acc = jnp.zeros((4096, 128), jnp.float32)
    for b in range(4096 // BLK):
        oh = (idxf[:, None] == (jax.lax.broadcasted_iota(
            jnp.int32, (4096, BLK), 1) + b * BLK)).astype(jnp.float32)
        acc += jax.lax.dot(oh, x_ref[pl.ds(b * BLK, BLK), :].astype(
            jnp.float32), precision=jax.lax.Precision.HIGHEST)
    o_ref[...] = acc.astype(jnp.int32)


@jax.jit
def onehot_gather(x, idx):
    return pl.pallas_call(kern_onehot,
                          out_shape=jax.ShapeDtypeStruct((4096, 128),
                                                         jnp.int32))(x, idx)


@functools.partial(jax.jit, static_argnums=2)
def xla_gather_scan(x, idx, R):
    def body(c, _):
        g = x[c]                                        # [4096, 128] gather
        c2 = (c + g[:, 0]) % jnp.int32(4096)
        return c2, None
    out, _ = jax.lax.scan(body, idx.reshape(4096), None, length=R)
    return out


@functools.partial(jax.jit, static_argnums=(2, 3))
def xla_scatter_gather_scan(dm, idx, R, n_idx):
    # deep-window-sized claim scatter-min + row gather per iteration
    def body(c, _):
        dmc = dm.at[c[:n_idx], 6].min(c[:n_idx])
        rows = dmc[c[:n_idx] % jnp.int32(65536)]
        c2 = (c + rows[: c.shape[0], 1].sum()) % jnp.int32(65536)
        return c2, None
    out, _ = jax.lax.scan(body, idx, None, length=R)
    return out


def main():
    print("backend:", jax.default_backend())
    x = jnp.arange(8 * 1024, dtype=jnp.int32).reshape(8, 1024)
    print("\nE. scan of pallas kernels")
    marginal(functools.partial(scan_pallas, x), (256, 1024, 2048), "pallas")
    print("\nF. scan of XLA fused step")
    marginal(functools.partial(scan_xla, x), (256, 1024, 2048), "xla   ")

    print("\nG. in-kernel dynamic row load+store")
    xg = jnp.arange(4096 * 128, dtype=jnp.int32).reshape(4096, 128)
    for R in (1024, 4096, 16384):
        t = timeit(dynrow, xg, R)
        print(f"  R={R:6d}: {t*1e3:8.2f} ms  ({t/R*1e6:.2f} us/row incl fixed)")

    print("\nH. scan of XLA gathers (marginal = true per-gather)")
    idx = ((jnp.arange(4096, dtype=jnp.int32) * jnp.int32(-1640531527)) % 4096)
    marginal(functools.partial(xla_gather_scan, xg, idx.reshape(8, 512)),
             (64, 256, 512), "gather")

    print("\nI. scan of XLA scatter-min+gather at [98k idx] on [65536,7]")
    dm = jnp.zeros((65536, 7), jnp.int32) + jnp.int32(2**30)
    idx = ((jnp.arange(98304, dtype=jnp.int32) * jnp.int32(-1640531527)) % 65536)
    for n_idx in (24576, 98304):
        f = functools.partial(xla_scatter_gather_scan, dm, idx)
        prev = None
        for R in (64, 256):
            t = timeit(lambda R=R: f(R, n_idx))
            if prev is not None:
                print(f"  n_idx={n_idx}: marginal "
                      f"{(t - prev[1]) / (R - prev[0]) * 1e6:.1f} us/iter")
            prev = (R, t)


if __name__ == "__main__":
    main()
