"""Probe: claim column as a separate dense [E] array vs the dm column.

The round's claim scatter-min writes a strided column of the [E, 7]
directory table, which makes XLA keep a transposed copy of the table
(PERF.md). This probe carries the claim column as its own [E] array in
the runner loop (scatter-min on a dense array, claims gathered
separately), leaving the table gather 7-wide but un-flipped. Run on
the TPU backend:

    python scripts/prof_claimsplit.py
"""

import dataclasses
import functools
import time

import numpy as np
import jax
import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu import codec
from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
from ue22cs343bb1_openmp_assignment_tpu.ops.pallas_window import (
    _SLOT_FIELDS, _call_replay, _call_window)
from ue22cs343bb1_openmp_assignment_tpu.ops.sync_engine import (
    ACT_DOWNGRADE, ACT_KILL, ACT_NONE, ACT_PROMOTE, DM_ACT, DM_COLS,
    DM_COUNT, DM_MEM, DM_OWNER, DM_REQ, DM_STATE, _round_key,
    claim_max_rounds)
from ue22cs343bb1_openmp_assignment_tpu.types import CacheState, DirState


def round_split(cfg, st, claim):
    """round_step_multi_pallas with the claim column split out."""
    N, C = cfg.num_nodes, cfg.cache_size
    K = cfg.txn_width
    E = N << cfg.block_bits
    INV = int(CacheState.INVALID)
    MOD = int(CacheState.MODIFIED)
    EXC = int(CacheState.EXCLUSIVE)
    SHD = int(CacheState.SHARED)
    rows0 = jnp.arange(N, dtype=jnp.int32)

    ca_t, cv_t, cs_t = (st.cache_addr.T, st.cache_val.T,
                        st.cache_state.T)
    idx2, cnt2 = st.idx[None, :], st.instr_count[None, :]
    slotmat, stepmat, cv_pre = _call_window(cfg, ca_t, cv_t, cs_t,
                                            idx2, cnt2)
    slot = {f: slotmat[i * K:(i + 1) * K]
            for i, f in enumerate(_SLOT_FIELDS + ("pos",))}
    W = cfg.drain_depth + K
    hc_w, dep_w, he_w = (stepmat[:W], stepmat[W:2 * W], stepmat[2 * W:])

    exists = slot["ok"].astype(bool)
    e1_s, e2_s = slot["e1"], slot["e2"]
    val_s, v_val_s = slot["val"], slot["v_val"]
    victim_s = slot["victim"].astype(bool)
    rd_s, wr_s, up_s = (slot["rd"].astype(bool), slot["wr"].astype(bool),
                        slot["up"].astype(bool))
    v_mod_s = slot["v_mod"].astype(bool) & victim_s
    rel_s = jnp.where(exists, slot["rel_ordn"], K)
    acqb_s = jnp.where(exists, slot["acq_basen"], K)
    pos_s = slot["pos"]

    key = _round_key(cfg, st, rows0)
    c_idx = jnp.concatenate(
        [jnp.where(exists[j], e1_s[j], E) for j in range(K)]
        + [jnp.where(victim_s[j], e2_s[j], E) for j in range(K)])
    claim = claim.at[c_idx].min(jnp.tile(key, 2 * K), mode="drop")
    # rows from the table, claims from the dense array — two gathers
    g = st.dm[jnp.concatenate([e1_s, e2_s], axis=0).reshape(-1)
              ].reshape(2 * K, N, DM_COLS)
    gc = claim[jnp.concatenate([e1_s, e2_s, he_w], axis=0).reshape(-1)
               ].reshape(2 * K + W, N)
    d1, d2 = g[:K], g[K:2 * K]
    c1, c2, hgot = gc[:K], gc[K:2 * K], gc[2 * K:]
    key1 = key[None, :]
    win = exists & (c1 == key1) & (~victim_s | (c2 == key1))

    d1s, d1c, d1o, d1m = (d1[..., DM_STATE], d1[..., DM_COUNT],
                          d1[..., DM_OWNER], d1[..., DM_MEM])
    d2c, d2o, d2m = d2[..., DM_COUNT], d2[..., DM_OWNER], d2[..., DM_MEM]
    pe_m = jnp.where(v_mod_s, v_val_s, d2m)
    base_u = jnp.zeros((K, N), bool)
    base_m = jnp.zeros((K, N), jnp.int32)
    for i in range(K):
        m = acqb_s == i
        base_u |= m
        base_m = jnp.where(m, pe_m[i:i + 1], base_m)
    d1s = jnp.where(base_u, int(DirState.U), d1s)
    d1c = jnp.where(base_u, 0, d1c)
    d1m = jnp.where(base_u, base_m, d1m)
    d_u = d1s == int(DirState.U)
    d_em = d1s == int(DirState.EM)

    prio_bits = max(1, (N - 1).bit_length())
    thresh = (jnp.maximum(claim_max_rounds(cfg) - st.round, 0) + 1) \
        << prio_bits
    first_bad_hit = jnp.full((N,), W, jnp.int32)
    for k in range(W):
        dep = dep_w[k]
        dok = jnp.zeros((N,), bool)
        for j in range(K):
            dok |= (dep == j) & d_u[j]
        unsafe = ((hc_w[k].astype(bool)
                   & ~((hgot[k] >= thresh) | (hgot[k] == key)))
                  | ((dep < K) & ~dok))
        first_bad_hit = jnp.minimum(first_bad_hit,
                                    jnp.where(unsafe, k, W))
    eligible = win & (pos_s < first_bad_hit[None, :])
    cum = []
    run = jnp.ones((N,), bool)
    for j in range(K):
        run = run & (eligible[j] | ~exists[j])
        cum.append(run)
    cum = jnp.stack(cum, axis=0)
    commit = exists & cum
    first_lose = jnp.minimum(
        jnp.min(jnp.where(exists & ~cum, pos_s, W), axis=0),
        first_bad_hit)

    rd_w, wr_w, up_w = commit & rd_s, commit & wr_s, commit & up_s
    wlike = wr_w | up_w
    ci_s = codec.cache_index(cfg, e1_s)
    safe_o = jnp.clip(d1o, 0, N - 1)
    val_o = cv_pre.reshape(-1)[ci_s * N + safe_o]
    n1s = jnp.where(wlike | (rd_w & d_u), int(DirState.EM),
                    int(DirState.S))
    n1c = jnp.where(wlike | (rd_w & d_u), 1,
                    jnp.where(rd_w & d_em, 2, d1c + 1))
    n1o = jnp.where(wlike | (rd_w & d_u), rows0[None, :], d1o)
    n1m = jnp.where((rd_w | wr_w) & d_em, val_o, d1m)
    act1 = jnp.where(wlike, ACT_KILL,
                     jnp.where(rd_w & d_em, ACT_DOWNGRADE, ACT_NONE))
    ev = commit & victim_s
    ev_mod = ev & v_mod_s
    ev_sh = ev & ~ev_mod
    n2c = jnp.where(ev_mod, 0, d2c - 1)
    n2s = jnp.where(n2c == 0, int(DirState.U),
                    jnp.where(n2c == 1, int(DirState.EM),
                              int(DirState.S)))
    n2m = jnp.where(ev_mod, v_val_s, d2m)
    act2 = jnp.where(ev_sh & (n2c == 1), ACT_PROMOTE, ACT_NONE)

    released = jnp.zeros((K, N), bool)
    rel_val = jnp.zeros((K, N), jnp.int32)
    rel_dirty = jnp.zeros((K, N), bool)
    consumed = jnp.zeros((K, N), bool)
    j_iota = jnp.arange(K, dtype=jnp.int32)[:, None]
    for r in range(K):
        m = commit[r:r + 1] & (rel_s[r:r + 1] == j_iota)
        released |= m
        rel_val = jnp.where(m, v_val_s[r:r + 1], rel_val)
        rel_dirty |= m & v_mod_s[r:r + 1]
        consumed |= commit[r:r + 1] & (acqb_s[r:r + 1] == j_iota)
    rd_rel_s = released & rd_s & ~d_u & ~d_em
    r1s = jnp.where(wlike | (rd_s & d_u), int(DirState.U),
                    jnp.where(rd_s & d_em, int(DirState.EM),
                              jnp.where(d1c == 1, int(DirState.EM),
                                        int(DirState.S))))
    r1c = jnp.where(wlike | (rd_s & d_u), 0,
                    jnp.where(rd_s & d_em, 1, d1c))
    r1m = jnp.where(wlike | rel_dirty, rel_val,
                    jnp.where(rd_s & d_em, val_o, d1m))
    r1a = jnp.where(wlike, ACT_KILL,
                    jnp.where((rd_s & d_em) | (rd_rel_s & (d1c == 1)),
                              ACT_PROMOTE, ACT_NONE))
    n1s = jnp.where(released, r1s, n1s)
    n1c = jnp.where(released, r1c, n1c)
    n1o = jnp.where(released, d1o, n1o)
    n1m = jnp.where(released, r1m, n1m)
    act1 = jnp.where(released, r1a, act1)
    ev_sep = ev & (rel_s == K) & ~consumed

    rtag = st.round << 2
    rowsK = jnp.broadcast_to(rows0[None, :], (K, N))
    t_idx = jnp.concatenate([jnp.where(commit, e1_s, E).reshape(-1),
                             jnp.where(ev_sep, e2_s, E).reshape(-1)])
    # 6 live columns; the table's 7th (claim) column is dead here and
    # written with zeros to keep DM_COLS layout
    zK = jnp.zeros((K, N), jnp.int32)
    t_dm = jnp.concatenate([
        jnp.stack([n1s, n1c, n1o, n1m, rtag | act1, rowsK, zK],
                  axis=-1).reshape(-1, DM_COLS),
        jnp.stack([n2s, n2c, d2o, n2m, rtag | act2, rowsK, zK],
                  axis=-1).reshape(-1, DM_COLS)])
    dm = st.dm.at[t_idx].set(t_dm, mode="drop")

    fill_state = jnp.where(rd_s, jnp.where(d_u, EXC, SHD), MOD)
    fill_val = jnp.where(rd_s, jnp.where(d_em, val_o, d1m), val_s)
    cache_mat, cnts = _call_replay(
        cfg, ca_t, cv_t, cs_t, idx2, cnt2, first_lose[None, :],
        fill_state, fill_val)
    ca_c, cv_c, cs_c = (cache_mat[:C], cache_mat[C:2 * C],
                        cache_mat[2 * C:])
    n_retired = cnts[0]

    line_e = jnp.clip(ca_c, 0, E - 1)
    line_dm = dm[line_e]
    fresh = (line_dm[..., DM_ACT] >> 2) == st.round
    a_code = jnp.where(fresh, line_dm[..., DM_ACT] & 3, ACT_NONE)
    a_req = line_dm[..., DM_REQ]
    valid = cs_c != INV
    not_self = a_req != rows0[None, :]
    kill = valid & not_self & (a_code == ACT_KILL)
    down = valid & not_self & (a_code == ACT_DOWNGRADE)
    promo = valid & not_self & (a_code == ACT_PROMOTE)
    cs_c = jnp.where(kill, INV,
                     jnp.where(down, SHD, jnp.where(promo, EXC, cs_c)))
    dm = dm.at[jnp.where(promo, line_e, E).reshape(-1), DM_OWNER].set(
        jnp.broadcast_to(rows0[None, :], (C, N)).reshape(-1),
        mode="drop")

    mt = st.metrics
    metrics = mt.replace(
        rounds=mt.rounds + 1,
        instrs_retired=mt.instrs_retired + jnp.sum(n_retired))
    new_st = st.replace(cache_addr=ca_c.T, cache_val=cv_c.T,
                        cache_state=cs_c.T, dm=dm,
                        idx=st.idx + n_retired, round=st.round + 1,
                        metrics=metrics)
    return new_st, claim


@functools.partial(jax.jit, static_argnums=(0, 2, 3))
def run_split(cfg, st, chunk, max_rounds):
    E = cfg.num_nodes << cfg.block_bits
    claim0 = jnp.full((E,), jnp.iinfo(jnp.int32).max, jnp.int32)

    def body(carry, _):
        s, c = carry
        return round_split(cfg, s, c), None

    def cond(carry):
        s, _ = carry
        return (~s.quiescent()) & (s.round < max_rounds)

    def chunk_body(carry):
        carry, _ = jax.lax.scan(body, carry, None, length=chunk)
        return carry

    final, _ = jax.lax.while_loop(cond, chunk_body, (st, claim0))
    return final


def main():
    cfg = SystemConfig.scale(num_nodes=4096, drain_depth=4, txn_width=3,
                             pallas_burst=True)
    cfg = dataclasses.replace(cfg, procedural="uniform", max_instrs=1)
    st = se.procedural_state(cfg, 4096)

    r = se.run_sync_to_quiescence(cfg, st, 64, 100000)
    base_ret = int(np.asarray(r.metrics.instrs_retired))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        r = se.run_sync_to_quiescence(cfg, st, 64, 100000)
        int(np.asarray(r.metrics.instrs_retired))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    print(f"baseline (claim in table): {base_ret/ts[1]:.3e} instrs/sec")

    f = run_split(cfg, st, 64, 100000)
    split_ret = int(np.asarray(f.metrics.instrs_retired))
    assert split_ret == base_ret, (split_ret, base_ret)
    np.testing.assert_array_equal(np.asarray(f.cache_val),
                                  np.asarray(r.cache_val))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        f = run_split(cfg, st, 64, 100000)
        int(np.asarray(f.metrics.instrs_retired))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    print(f"split dense claim array:   {split_ret/ts[1]:.3e} instrs/sec")


if __name__ == "__main__":
    main()
