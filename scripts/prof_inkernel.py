"""In-kernel cost scaling: ops-per-step vs time (throwaway)."""
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

STEPS = 2000


def bench(name, kernel, x):
    @jax.jit
    def run(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )(x)

    r = run(x)
    int(r.ravel()[0])
    t0 = time.perf_counter()
    r = run(x)
    int(r.ravel()[0])
    dt = time.perf_counter() - t0
    print(f"{name:56s} {dt/STEPS*1e6:9.2f} us/step")


def mk(n_ops):
    def kernel(x_ref, o_ref):
        def body(i, acc):
            for k in range(n_ops):
                acc = acc + (acc & (k + 1))
            return acc
        o_ref[:] = jax.lax.fori_loop(0, STEPS, body, x_ref[:])
    return kernel


small = jnp.ones((8, 128), jnp.int32)       # 1 native tile
med = jnp.ones((256, 128), jnp.int32)       # 32k elems
big = jnp.ones((4096, 128), jnp.int32)      # 512k elems

for n_ops in (2, 8, 32, 128):
    bench(f"[8,128]    {n_ops:3d} int ops/step", mk(n_ops), small)
for n_ops in (2, 8, 32):
    bench(f"[256,128]  {n_ops:3d} int ops/step", mk(n_ops), med)
for n_ops in (2, 8):
    bench(f"[4096,128] {n_ops:3d} int ops/step", mk(n_ops), big)


# dynamic-index load/store inside kernel (the delivery primitive)
def dyn_kernel(x_ref, o_ref):
    def body(i, acc):
        j = (i * 7) % 256
        row = x_ref[j, :]          # dynamic row load
        o_ref[(j + 1) % 256, :] = row + acc[0, 0]
        return acc + 1
    o_ref[:] = x_ref[:]
    acc = jax.lax.fori_loop(0, STEPS, body, jnp.ones((8, 128), jnp.int32))
    o_ref[0, :] = acc[0, :]

bench("[256,128] dynamic row load+store per step", dyn_kernel, med)
