"""Finer primitive microbenchmarks (throwaway)."""
import time

import jax
import jax.numpy as jnp

N, K = 4096, 512


def bench(name, body, *xs):
    @jax.jit
    def run(*xs):
        def step(c, _):
            return body(*c), None
        out, _ = jax.lax.scan(step, xs, None, length=K)
        return out

    r = run(*xs)
    int(jax.tree.leaves(r)[0].ravel()[0])  # device_get = real sync
    t0 = time.perf_counter()
    r = run(*xs)
    int(jax.tree.leaves(r)[0].ravel()[0])
    dt = time.perf_counter() - t0
    print(f"{name:44s} {dt/K*1e6:9.1f} us/iter")


v = jnp.ones((N,), jnp.int32)

bench("add/xor/and x20 [4096]",
      lambda v: ((v + 1) ^ (v + 2) & (v + 3) | (v - 4) + (v + 5)
                 ^ (v + 6) + (v + 7) & (v + 8) + (v + 9) ^ (v + 10),), v)

bench("one signed mod %97 [4096]", lambda v: (v % 97 + 1,), v)
bench("one signed div //7 [4096]", lambda v: (v // 7 + 1,), v)
bench("one uint32 mod %97 [4096]",
      lambda v: (v % jnp.uint32(97) + 1,), v.astype(jnp.uint32))
bench("mod by pow2 &63 [4096]", lambda v: ((v & 63) + 1,), v)

m = jnp.ones((N, 16), jnp.int32)
bench("add x5 [4096,16]",
      lambda m: (m + 1 + (m ^ 3) + (m & 7) + (m | 9) + 2,), m)

# scalar dynamic-slice in carry (v[0]) cost
bench("v[0] scalar extract in carry",
      lambda v: (v + v[0],), v)

# int64 presence check
bench("i32 mul-hi via 64-bit? (v*v)>>1",
      lambda v: ((v * v) >> 1,), v)

idx = jnp.arange(N, dtype=jnp.int32) % 16
bench("take_along_axis [4096,16] axis1",
      lambda m, i: (m + 1, (i + m[jnp.arange(N), i][0]) % 16), m, idx)

# argsort variants F=12288
F = 12288
key = (jnp.arange(F, dtype=jnp.int32) * 264435761 % 100003)
bench("argsort [12288]", lambda k: (jnp.argsort(k) % 7 + k[:1],), key)
bench("sort-pair (k,iota) lax.sort 2-operand",
      lambda k: (jax.lax.sort((k, jnp.arange(F, dtype=jnp.int32)),
                              num_keys=1)[1] % 7 + k[:1],), key)
ku = key.astype(jnp.uint32)
bench("sort u32 keys only", lambda k: (jnp.sort(k) + k[:1],), ku)
