"""Deep-engine throughput sweep on the attached TPU.

Measures sustained instrs/sec at the headline config (4096 nodes,
procedural uniform local_frac 0.8) across window length W and slot
budgets, against the multi-txn engine baseline. Timing: device_get
sync, median of reps, one-dispatch runs (chunked while_loop).
"""

import argparse
import dataclasses
import time

import jax
import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se


def run_cfg(cfg, length, chunk=64, reps=3, max_rounds=60_000):
    st0 = se.procedural_state(cfg, length)

    def run():
        return se.run_sync_to_quiescence(cfg, st0, chunk, max_rounds)

    out = run()
    retired = int(np.asarray(out.metrics.instrs_retired))  # warm + sync
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run()
        retired = int(np.asarray(out.metrics.instrs_retired))
        times.append(time.perf_counter() - t0)
    times.sort()
    el = times[len(times) // 2]
    rounds = int(np.asarray(out.metrics.rounds))
    q = bool(out.quiescent())
    return retired / el, rounds, retired, q, el


def check_identity(N=1024, dd=13, tw=3, Q=8, G=4):
    """Full-size XLA vs Pallas deep-round bit-identity on the TPU."""
    import numpy as np_
    cfg = SystemConfig.scale(N, drain_depth=dd, txn_width=tw)
    cfg = dataclasses.replace(cfg, procedural="uniform", max_instrs=1,
                              deep_window=True, deep_slots=Q,
                              deep_ownerval_slots=G)
    pcfg = dataclasses.replace(cfg, pallas_burst=True)
    st = se.procedural_state(cfg, 256, seed=3)
    st = se.run_rounds(cfg, st, 20)
    a = se.run_rounds(cfg, st, 8)
    b = se.run_rounds(pcfg, st, 8)
    import jax as j
    for x, y in zip(j.tree_util.tree_leaves(a), j.tree_util.tree_leaves(b)):
        np_.testing.assert_array_equal(np_.asarray(x), np_.asarray(y))
    print(f"identity OK: XLA == Pallas over 8 warmed rounds (N={N})")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4096)
    ap.add_argument("--len", type=int, default=4096)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--baseline", action="store_true")
    ap.add_argument("--pallas", action="store_true",
                    help="route deep rounds through ops.pallas_deep")
    ap.add_argument("--identity", action="store_true",
                    help="run the full-size XLA-vs-Pallas identity check")
    args = ap.parse_args()
    N, L = args.nodes, args.len
    print(f"backend={jax.default_backend()} N={N} len={L}")
    if args.identity:
        check_identity()

    if args.baseline:
        cfg = SystemConfig.scale(N, drain_depth=4, txn_width=3)
        cfg = dataclasses.replace(cfg, procedural="uniform", max_instrs=1,
                                  pallas_burst=True)
        r, rounds, ret, q, el = run_cfg(cfg, L, reps=args.reps)
        print(f"multi K=3 pallas: {r:.3e} i/s rounds={rounds} q={q} "
              f"({ret/rounds/N:.2f}/node/round, {el*1e3/rounds:.2f} ms/round)")

    for (dd, tw, Q, G, slack) in [
        (13, 3, 6, 3, 2),
        (13, 3, 8, 4, 2),
        (13, 3, 8, 4, 6),
        (21, 3, 8, 4, 8),
        (21, 3, 10, 4, 16),
        (29, 3, 12, 4, 16),
        (45, 3, 12, 4, 32),
        (5, 3, 6, 3, 2),
    ]:
        cfg = SystemConfig.scale(N, drain_depth=dd, txn_width=tw)
        cfg = dataclasses.replace(cfg, procedural="uniform", max_instrs=1,
                                  deep_window=True, deep_slots=Q,
                                  deep_ownerval_slots=G,
                                  deep_horizon_slack=slack,
                                  pallas_burst=args.pallas)
        try:
            r, rounds, ret, q, el = run_cfg(cfg, L, reps=args.reps)
        except Exception as e:
            print(f"deep W={dd+tw} Q={Q} G={G} s={slack}: FAILED "
                  f"{str(e)[:100]}")
            continue
        print(f"deep W={dd+tw} Q={Q} G={G} s={slack}: {r:.3e} i/s "
              f"rounds={rounds} q={q} ({ret/rounds/N:.2f}/node/round, "
              f"{el*1e3/rounds:.2f} ms/round)")


if __name__ == "__main__":
    main()
