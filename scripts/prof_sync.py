"""Per-round cost ablation for the sync engine (throwaway)."""
import time

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se

K = 256


def timeit(cfg, st):
    se._run_rounds_jit.clear_cache()
    out = se.run_rounds(cfg, st, K)
    int(out.metrics.rounds)
    t0 = time.perf_counter()
    out = se.run_rounds(cfg, st, K)
    int(out.metrics.rounds)
    return (time.perf_counter() - t0) / K * 1e6


for H in (0, 2, 8, 16):
    cfg = SystemConfig.scale(num_nodes=4096, drain_depth=H)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=96, seed=0)
    st = se.from_sim_state(cfg, sys_.state)
    print(f"drain_depth={H:2d}: {timeit(cfg, st):8.1f} us/round")
