"""Pre-build probes for the deep-window engine.

J. Does a drop-mode scatter/gather pay for PADDED (out-of-range)
   indices? Compares all-real vs 75%-padded at equal slot counts.
K. Fold-sized Pallas kernel cost: ~W*170 vector ops on [1,1024] rows,
   embedded in a scan — marginal per call.
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def sync(x):
    return float(np.asarray(jax.device_get(x)).ravel()[0])


def timeit(fn, *args, reps=5):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def marg(f, Rs=(64, 256)):
    t1 = timeit(f, Rs[0])
    t2 = timeit(f, Rs[1])
    return (t2 - t1) / (Rs[1] - Rs[0]) * 1e6


@functools.partial(jax.jit, static_argnums=(2,))
def scat_gath(dm, idx, R):
    E = dm.shape[0]

    def body(c, _):
        dmc = dm.at[c, 6].min(c)
        rows = dmc[jnp.where(c < E, c, 0)]
        c2 = (c + rows[:, 1]) % jnp.int32(E + E // 4)
        return c2, None
    out, _ = jax.lax.scan(body, idx, None, length=R)
    return out


def kern_fold(W, x_ref, o_ref):
    rows = [x_ref[i:i + 1, :] for i in range(16)]
    acc = x_ref[0:1, :]
    for k in range(W):
        b = (acc & jnp.int32(15))
        sel = rows[0]
        for c in range(1, 16):
            sel = jnp.where(b == c, rows[c], sel)      # 16-way own-row read
        for _ in range(24):                            # misc fold arithmetic
            acc = (acc * jnp.int32(3) + sel) ^ (acc >> 7)
        nb = acc & jnp.int32(15)
        rows = [jnp.where(nb == c, acc, r) for c, r in enumerate(rows)]
    o_ref[...] = jnp.concatenate(
        [r + (acc & jnp.int32(0)) for r in rows], axis=0)


@functools.partial(jax.jit, static_argnums=(1, 2))
def scan_fold(x, W, R):
    shape = jax.ShapeDtypeStruct((16, 1024), jnp.int32)

    def body(c, _):
        o = pl.pallas_call(functools.partial(kern_fold, W),
                           out_shape=shape,
                           grid=(4,),
                           in_specs=[pl.BlockSpec((16, 1024),
                                                  lambda i: (0, i))],
                           out_specs=pl.BlockSpec((16, 1024),
                                                  lambda i: (0, i)))(c)
        return o, None
    out, _ = jax.lax.scan(body, x, None, length=R)
    return out


def main():
    print("backend:", jax.default_backend())
    E = 65536
    dm = jnp.full((E, 7), 2**30, jnp.int32)
    n = 57344                       # 14 slots x 4096 nodes
    base = ((jnp.arange(n, dtype=jnp.int32) * jnp.int32(-1640531527))
            % E)
    print("J. scatter+gather pair, 57K slots")
    for frac_real, name in ((1.0, "all real"), (0.25, "75% padded")):
        k = int(n * frac_real)
        idx = jnp.where(jnp.arange(n) < k, base, E)   # E = dropped
        m = marg(functools.partial(scat_gath, dm, idx))
        print(f"  {name}: marginal {m:.1f} us/iter")

    print("K. fold-sized pallas kernel (4 tiles of [16,1024])")
    x = jnp.arange(16 * 1024, dtype=jnp.int32).reshape(16, 1024) & 0xFF
    for W in (8, 24):
        m = marg(functools.partial(scan_fold, x, W))
        print(f"  W={W}: marginal {m:.1f} us/call")


if __name__ == "__main__":
    main()
