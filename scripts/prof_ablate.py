"""Ablation profiling of the cycle on the real device.

Times run_cycles variants with pieces of the cycle stubbed out to see
where device time goes. Throwaway diagnostic; not part of the package.
"""
import time

import jax
import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.ops import mailbox, step

N = 4096
K = 512  # cycles per timed dispatch

cfg = SystemConfig.scale(num_nodes=N)
sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=32, seed=0)
state = sys_.state


def timeit(fn, state):
    fn.clear_cache()  # monkeypatched internals don't invalidate jit caches
    out = fn(cfg, state, K)
    int(out.metrics.cycles)  # device_get sync
    t0 = time.perf_counter()
    out = fn(cfg, state, K)
    int(out.metrics.cycles)
    dt = time.perf_counter() - t0
    return dt


# 1. full cycle
full = timeit(step.run_cycles, state)
print(f"full cycle:          {full/K*1e6:9.1f} us/cycle  ({K} cycles in {full:.3f}s)")

# 2. no delivery at all (messages vanish) — measures all of phase 3
def deliver_null(cfg, state, cand, arb_rank, new_head, new_count):
    z = jnp.zeros((), jnp.int32)
    return dict(mb_head=new_head, mb_count=new_count,
                fault_key=state.fault_key), z, z

mailbox.deliver = deliver_null
nodeliv = timeit(step.run_cycles, state)
print(f"null delivery:       {nodeliv/K*1e6:9.1f} us/cycle   (delivery total ~{(full-nodeliv)/K*1e6:.1f} us)")
mailbox.deliver = orig_deliver  # noqa: F841
