"""Ablation profiling of the cycle on the real device.

Times run_cycles variants with pieces of the cycle stubbed out to see
where device time goes. Throwaway diagnostic; not part of the package.
"""
import time

import jax
import jax.numpy as jnp

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.ops import mailbox, step

N = 4096
K = 512  # cycles per timed dispatch

cfg = SystemConfig.scale(num_nodes=N)
sys_ = CoherenceSystem.from_workload(cfg, "uniform", trace_len=32, seed=0)
state = sys_.state


def timeit(fn, state):
    fn.clear_cache()  # monkeypatched internals don't invalidate jit caches
    out = fn(cfg, state, K)
    int(out.metrics.cycles)  # device_get sync
    t0 = time.perf_counter()
    out = fn(cfg, state, K)
    int(out.metrics.cycles)
    dt = time.perf_counter() - t0
    return dt


# 1. full cycle
full = timeit(step.run_cycles, state)
print(f"full cycle:          {full/K*1e6:9.1f} us/cycle  ({K} cycles in {full:.3f}s)")

# 2. delivery with no sort (identity order) — measures the argsort cost
orig_deliver = mailbox.deliver

def deliver_nosort(cfg, state, cand, arb_rank, new_head, new_count):
    N_, S = cfg.num_nodes, cfg.out_slots
    F = N_ * S
    c_type = cand.type.reshape(F)
    recv = cand.recv.reshape(F)
    valid = (c_type != 0) & (recv >= 0) & (recv < N_)
    order = jnp.arange(F)
    r_s, v_s = recv, valid
    idx = jnp.arange(F, dtype=jnp.int32)
    is_start = jnp.concatenate([jnp.array([True]), (r_s[1:] != r_s[:-1]) | ~v_s[1:]])
    seg_start = mailbox.jax_cummax(jnp.where(is_start, idx, -1))
    rank = idx - seg_start
    safe_r = jnp.where(v_s, r_s, 0)
    free = (cfg.queue_capacity - new_count)[safe_r]
    accept = v_s & (rank < free)
    dropped = jnp.sum(v_s & ~accept).astype(jnp.int32)
    pos = (new_head[safe_r] + new_count[safe_r] + rank) % cfg.queue_capacity
    tgt_r = jnp.where(accept, r_s, N_)
    tgt_p = jnp.where(accept, pos, 0)

    def put(arr, field):
        vals = field.reshape(F) if field.ndim == 2 else field.reshape(F, -1)
        return arr.at[tgt_r, tgt_p].set(vals, mode="drop")

    updates = dict(
        mb_type=put(state.mb_type, cand.type),
        mb_sender=put(state.mb_sender, cand.sender),
        mb_addr=put(state.mb_addr, cand.addr),
        mb_value=put(state.mb_value, cand.value),
        mb_second=put(state.mb_second, cand.second),
        mb_dirstate=put(state.mb_dirstate, cand.dirstate),
        mb_bitvec=state.mb_bitvec.at[tgt_r, tgt_p].set(
            cand.bitvec.reshape(F, -1), mode="drop"),
        mb_head=new_head,
        mb_count=new_count.at[tgt_r].add(accept.astype(jnp.int32), mode="drop"),
        fault_key=state.fault_key,
    )
    return updates, dropped, jnp.zeros((), jnp.int32)

mailbox.deliver = deliver_nosort
nosort = timeit(step.run_cycles, state)
print(f"no-sort delivery:    {nosort/K*1e6:9.1f} us/cycle   (sort cost ~{(full-nosort)/K*1e6:.1f} us)")

# 3. no delivery at all (messages vanish) — measures all of phase 3
def deliver_null(cfg, state, cand, arb_rank, new_head, new_count):
    z = jnp.zeros((), jnp.int32)
    return dict(mb_head=new_head, mb_count=new_count,
                fault_key=state.fault_key), z, z

mailbox.deliver = deliver_null
nodeliv = timeit(step.run_cycles, state)
print(f"null delivery:       {nodeliv/K*1e6:9.1f} us/cycle   (delivery total ~{(full-nodeliv)/K*1e6:.1f} us)")
mailbox.deliver = orig_deliver
