"""L. Does scatter/gather cost scale with ROW WIDTH at fixed index count?

The deep round's composition pays a [E, 7] row gather + [E, 7] row
scatter per wave (ops/deep_engine request composition). If cost scales
with gathered/scattered ELEMENTS (indices x width) rather than indices
alone, packing the 7 int32 columns into fewer words is a direct win;
if cost is per-index only, packing buys nothing. Measures the marginal
cost of a gather+scatter pair over widths 1/2/4/7 at the headline
round's index count (N*Q = 12288 on E = 16384 rows), plus the 65536-row
variant for the ladder.
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np


def sync(x):
    return float(np.asarray(jax.device_get(x)).ravel()[0])


def timeit(fn, *args, reps=5):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def marg(f, Rs=(64, 256)):
    t1 = timeit(f, Rs[0])
    t2 = timeit(f, Rs[1])
    return (t2 - t1) / (Rs[1] - Rs[0]) * 1e6


@functools.partial(jax.jit, static_argnums=(2,))
def pair(dm, idx, R):
    E = dm.shape[0]
    W = dm.shape[1]

    def body(c, _):
        carry_idx, d = c
        rows = d[jnp.clip(carry_idx, 0, E - 1)]          # [n, W] gather
        d2 = d.at[carry_idx].set(rows + 1, mode="drop")  # [n, W] scatter
        nxt = (carry_idx + rows[:, 0]) % jnp.int32(E + E // 4)
        return (nxt, d2), None
    (out, d), _ = jax.lax.scan(body, (idx, dm), None, length=R)
    return out


def main():
    print("backend:", jax.default_backend())
    n = 12288                       # 3 slots x 4096 nodes
    for E in (16384, 65536 * 16):
        base = ((jnp.arange(n, dtype=jnp.int32)
                 * jnp.int32(-1640531527)) % E)
        print(f"L. gather+scatter pair, {n} idx, E={E}")
        for W in (1, 2, 4, 7):
            dm = jnp.zeros((E, W), jnp.int32)
            m = marg(functools.partial(pair, dm, base))
            print(f"  width {W}: marginal {m:.1f} us/iter")


if __name__ == "__main__":
    main()
