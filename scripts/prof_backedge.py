"""Calibrate in-kernel loop economics on the attached TPU.

Round-2 throughput design hinges on how a `fori_loop` *inside* one
jitted Pallas kernel prices per-iteration work, versus dispatching one
kernel per round from a jitted scan (PERF.md's ~100-700 us/kernel).
PERF.md's earlier 25-100 us/backedge figure came from eager standalone
launches (scripts/prof_inkernel*.py); this script re-measures under the
real conditions: kernels embedded in jit, synced via device_get.

Measures:
  A. jitted pallas_call, in-kernel fori_loop(R) with a small vector body
     on a [8, 1024] block — cost vs R isolates the backedge.
  B. same, nested fori (outer R, inner 64) — do nested backedges pay?
  C. jitted lax.scan of R pallas_calls (1 kernel/iter) — the dispatch
     alternative.
  D. in-kernel fori over a body with ~32 vector ops (a round-fold-sized
     body) — per-op cost inside a loop.
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def sync(x):
    return float(np.asarray(jax.device_get(x)).ravel()[0])


def timeit(fn, *args, reps=3):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def kern_fori(R, x_ref, o_ref):
    def body(i, acc):
        return acc * jnp.int32(3) + jnp.int32(1) ^ (acc >> 7)
    o_ref[...] = jax.lax.fori_loop(0, R, body, x_ref[...])


def kern_nested(R, inner, x_ref, o_ref):
    def ibody(j, acc):
        return acc * jnp.int32(3) + jnp.int32(1) ^ (acc >> 7)

    def body(i, acc):
        return jax.lax.fori_loop(0, inner, ibody, acc)
    o_ref[...] = jax.lax.fori_loop(0, R, body, x_ref[...])


def kern_fat(R, x_ref, o_ref):
    def body(i, acc):
        for _ in range(16):  # ~32 vector ops
            acc = acc * jnp.int32(3) + jnp.int32(1)
            acc = acc ^ (acc >> 7)
        return acc
    o_ref[...] = jax.lax.fori_loop(0, R, body, x_ref[...])


def pcall(kern, R, *extra):
    shape = jax.ShapeDtypeStruct((8, 1024), jnp.int32)

    @jax.jit
    def run(x):
        return pl.pallas_call(functools.partial(kern, R, *extra),
                              out_shape=shape)(x)
    return run


def main():
    x = jnp.arange(8 * 1024, dtype=jnp.int32).reshape(8, 1024)
    print("backend:", jax.default_backend())

    print("\nA. in-kernel fori, trivial body")
    prev = None
    for R in (64, 256, 1024, 4096):
        t = timeit(pcall(kern_fori, R), x)
        d = "" if prev is None else f"  marginal/iter: {(t - prev[1]) / (R - prev[0]) * 1e6:.2f} us"
        print(f"  R={R:5d}: {t*1e3:8.2f} ms{d}")
        prev = (R, t)

    print("\nB. nested fori, outer x inner=64, trivial body")
    for R in (64, 256):
        t = timeit(pcall(kern_nested, R, 64), x)
        print(f"  R={R:5d} (total {R*64}): {t*1e3:8.2f} ms "
              f"({t / (R*64) * 1e6:.2f} us/total-iter)")

    print("\nC. jitted scan of R pallas_calls (dispatch alternative)")
    shape = jax.ShapeDtypeStruct((8, 1024), jnp.int32)

    def one(x_ref, o_ref):
        o_ref[...] = x_ref[...] * jnp.int32(3) + jnp.int32(1) ^ (x_ref[...] >> 7)

    @functools.partial(jax.jit, static_argnums=1)
    def scan_calls(x, R):
        def body(c, _):
            return pl.pallas_call(one, out_shape=shape)(c), None
        out, _ = jax.lax.scan(body, x, None, length=R)
        return out
    prev = None
    for R in (16, 64, 256):
        t = timeit(scan_calls, x, R)
        d = "" if prev is None else f"  marginal/call: {(t - prev[1]) / (R - prev[0]) * 1e6:.1f} us"
        print(f"  R={R:5d}: {t*1e3:8.2f} ms{d}")
        prev = (R, t)

    print("\nD. in-kernel fori, ~32-op body")
    prev = None
    for R in (64, 256, 1024):
        t = timeit(pcall(kern_fat, R), x)
        d = "" if prev is None else f"  marginal/iter: {(t - prev[1]) / (R - prev[0]) * 1e6:.2f} us"
        print(f"  R={R:5d}: {t*1e3:8.2f} ms{d}")
        prev = (R, t)


if __name__ == "__main__":
    main()
