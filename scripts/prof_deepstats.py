"""Deep-round anatomy on the attached TPU: what fills slots, what
truncates windows, what caps committed depth (~4.5 at the headline
config despite horizon slack — the round-3 question).

Runs warm rounds at the given config, then collects round_step_deep's
return_stats sums over a few rounds and prints per-node-per-round
averages.
"""

import argparse
import dataclasses

import jax
import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.ops import sync_engine as se
from ue22cs343bb1_openmp_assignment_tpu.ops.deep_engine import (
    round_step_deep)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4096)
    ap.add_argument("--len", type=int, default=2048)
    ap.add_argument("--warm", type=int, default=40)
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--dd", type=int, default=13)
    ap.add_argument("--tw", type=int, default=3)
    ap.add_argument("--slots", type=int, default=6)
    ap.add_argument("--g", type=int, default=3)
    ap.add_argument("--slack", type=int, default=2)
    ap.add_argument("--local", type=int, default=800)
    ap.add_argument("--waves", type=int, default=1)
    ap.add_argument("--storm", action="store_true")
    ap.add_argument("--no-exact-flags", action="store_true")
    ap.add_argument("--workload", default=None,
                    help="stored workload (models.workloads name) "
                         "instead of the procedural uniform source")
    args = ap.parse_args()
    N = args.nodes
    cfg = SystemConfig.scale(N, drain_depth=args.dd, txn_width=args.tw)
    proc = {} if args.workload else dict(procedural="uniform",
                                         max_instrs=1)
    cfg = dataclasses.replace(
        cfg, proc_local_permille=args.local, deep_window=True,
        deep_slots=args.slots, deep_ownerval_slots=args.g,
        deep_horizon_slack=args.slack, deep_waves=args.waves,
        deep_read_storm=args.storm,
        deep_exact_flags=not args.no_exact_flags, **proc)
    print(f"backend={jax.default_backend()} N={N} W={args.dd + args.tw} "
          f"Q={args.slots} slack={args.slack} local={args.local}")
    if args.workload:
        from ue22cs343bb1_openmp_assignment_tpu.models.system import (
            CoherenceSystem)
        st = se.from_sim_state(
            cfg, CoherenceSystem.from_workload(
                cfg, args.workload, trace_len=args.len, seed=0).state,
            seed=0)
    else:
        st = se.procedural_state(cfg, args.len, seed=0)
    st = se.run_rounds(cfg, st, args.warm)

    step = jax.jit(lambda s: round_step_deep(cfg, s, return_stats=True))
    acc = None
    for _ in range(args.rounds):
        st, stats = step(st)
        stats = {k: int(v) for k, v in stats.items()}
        acc = stats if acc is None else {
            k: acc[k] + v for k, v in stats.items()}
    R = args.rounds
    per = {k: v / R / N for k, v in acc.items()}
    print(f"per node per round (avg over {R} rounds):")
    print(f"  retired {per['n_ret']:.2f}  horizon {per['horizon_sum']:.2f}"
          f"  slots used {per['n_slot']:.2f}")
    print(f"  attempts: rd {per['att_rd']:.2f} wr {per['att_wr']:.2f} "
          f"up {per['att_up']:.2f} evS {per['att_evs']:.2f} "
          f"evM {per['att_evm']:.2f} probe {per['att_probe']:.2f}")
    print(f"  lane losses {per['lost']:.3f}  poison aborts "
          f"{per['abort_poison']:.3f}  mark aborts {per['abort_mark']:.3f}"
          f"  probe bad {per['probe_bad']:.3f}")
    print(f"  committed slots {per['committed']:.2f}  released "
          f"{per['released']:.3f}  storm grants {per['storm']:.3f}")
    print(f"  frac nodes truncated {per['truncated']:.3f}  stopped "
          f"{per['stopped']:.3f}  past-first-request {per['seen_req']:.3f}")
    print(f"  clean (no post-request own touches) {per['clean']:.3f}")
    print(f"  stop reasons: over_q {per['stop_overq']:.3f}  over_g "
          f"{per['stop_overg']:.3f}  dup {per['stop_dup']:.3f}  dep "
          f"{per['stop_dep']:.3f}  trace-end {per['stop_live']:.3f}")


if __name__ == "__main__":
    main()
