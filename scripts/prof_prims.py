"""Microbenchmark TPU primitive costs inside a scan (throwaway)."""
import time

import jax
import jax.numpy as jnp

N, C, Q, K = 4096, 16, 64, 512


def bench(name, body, *xs):
    @jax.jit
    def run(*xs):
        def step(c, _):
            return body(*c), None
        out, _ = jax.lax.scan(step, xs, None, length=K)
        return out

    r = run(*xs)
    int(jax.tree.leaves(r)[0].ravel()[0])  # device_get = real sync
    t0 = time.perf_counter()
    r = run(*xs)
    int(jax.tree.leaves(r)[0].ravel()[0])  # device_get sync
    dt = time.perf_counter() - t0
    print(f"{name:44s} {dt/K*1e6:9.1f} us/iter")


arr = jnp.zeros((N, C), jnp.int32)
idx = jnp.arange(N, dtype=jnp.int32) % C
val = jnp.arange(N, dtype=jnp.int32)
rows = jnp.arange(N, dtype=jnp.int32)

bench("row-scatter arr.at[rows, idx].set",
      lambda a, i, v: (a.at[rows, i].set(v), (i + v[0]) % C, v + 1),
      arr, idx, val)

bench("row-scatter as one-hot where",
      lambda a, i, v: (jnp.where(jnp.arange(C)[None, :] == i[:, None],
                                 v[:, None], a), (i + v[0]) % C, v + 1),
      arr, idx, val)

bench("row-gather arr[rows, idx]",
      lambda a, i, v: (a, (i + a[rows, i][0]) % C, v + 1), arr, idx, val)

bench("row-gather as one-hot sum",
      lambda a, i, v: (a, (i + jnp.sum(
          jnp.where(jnp.arange(C)[None, :] == i[:, None], a, 0),
          axis=1)[0]) % C, v + 1), arr, idx, val)

big = jnp.zeros((N, Q), jnp.int32)
F = N * 3
tr = jnp.arange(F, dtype=jnp.int32) % N
tp = jnp.arange(F, dtype=jnp.int32) % Q
fv = jnp.arange(F, dtype=jnp.int32)

bench("free scatter [F]->[N,Q] .at[tr,tp].set",
      lambda a, r, p, v: (a.at[r, p].set(v, mode="drop"),
                          (r + v[0]) % N, p, v + 1), big, tr, tp, fv)

bench("free gather [N,Q]<-[F] flat-index",
      lambda a, r, p, v: (a, (r + a.reshape(-1)[(r * Q + p) % (N * Q)][0]) % N,
                          p, v + 1), big, tr, tp, fv)

key = jnp.arange(F, dtype=jnp.int32)[::-1]
bench("argsort [12288] i32",
      lambda k: ((jnp.argsort(k) + k[0]).astype(jnp.int32),), key)

bench("sort [12288] i32 keys only",
      lambda k: ((jnp.sort(k) + k[0]).astype(jnp.int32),), key)

two = jnp.zeros((N,), jnp.int32)
bench("pure elementwise [N] x20",
      lambda v: (((v * 3 + 1) % 1000 + (v // 7) * 2 - (v ^ 5) + (v & 31)
                  + (v | 2) - (v % 13) + v * v % 97,)), two)
