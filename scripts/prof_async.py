"""Async-engine cycle cost decomposition on the attached TPU.

VERDICT r2 #10: the async (parity) engine sustains ~3.4e5 instrs/sec —
40x below sync — and the round-1 "~50 kernels/cycle" explanation is
obsolete under the corrected device model (kernels in a jitted scan
are ~free; index count and sorts are the currency). This script
isolates where an async cycle's time actually goes:

  A. marginal full-cycle cost in a long scan (the real number)
  B. deliver-only: mailbox.deliver in a scan with synthetic candidates
  C. sort-only: the (recv, prio) two-operand sort at candidate size

Timing: device_get sync, marginal over two scan lengths (PERF.md).
"""

import argparse
import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig
from ue22cs343bb1_openmp_assignment_tpu.models.system import CoherenceSystem
from ue22cs343bb1_openmp_assignment_tpu.ops import mailbox
from ue22cs343bb1_openmp_assignment_tpu.ops.step import (_ro_outside, cycle)


def sync(x):
    return float(np.asarray(jax.device_get(x)).ravel()[0])


def timeit(fn, *args, reps=5):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def marginal(fn, r1, r2):
    t1, t2 = timeit(fn, r1), timeit(fn, r2)
    return (t2 - t1) / (r2 - r1) * 1e6


@functools.partial(jax.jit, static_argnums=(0, 2))
def run_cycles_r(cfg, state, R):
    carry0, ro, blanks = _ro_outside(state)

    def body(s, _):
        out = cycle(cfg, s.replace(**ro))
        return out.replace(**blanks), None

    final, _ = jax.lax.scan(body, carry0, None, length=R)
    return final.replace(**ro).metrics.cycles


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4096)
    ap.add_argument("--len", type=int, default=256)
    args = ap.parse_args()
    N = args.nodes
    print(f"backend={jax.default_backend()} N={N}")
    cfg = SystemConfig.scale(num_nodes=N)
    sys_ = CoherenceSystem.from_workload(cfg, "uniform",
                                         trace_len=args.len, seed=0,
                                         local_frac=0.8)
    st = sys_.state

    m = marginal(lambda R: run_cycles_r(cfg, st, R), 64, 448)
    print(f"A. full cycle marginal: {m:.0f} us/cycle")

    # B: deliver-only in a scan (synthetic candidates, ~0.5 real/node)
    S = 3
    rng = np.random.default_rng(0)
    send = rng.random((N, S)) < 0.17
    cand = mailbox.Candidates(
        type=jnp.asarray(np.where(send, 1, 0), jnp.int32),
        recv=jnp.asarray(rng.integers(0, N, (N, S)), jnp.int32),
        sender=jnp.asarray(np.broadcast_to(np.arange(N)[:, None], (N, S)),
                           jnp.int32),
        addr=jnp.asarray(rng.integers(0, 256, (N, S)), jnp.int32),
        value=jnp.asarray(rng.integers(0, 256, (N, S)), jnp.int32),
        second=jnp.zeros((N, S), jnp.int32),
        dirstate=jnp.zeros((N, S), jnp.int32),
        bitvec=jnp.zeros((N, S, cfg.msg_bitvec_words), jnp.uint32))
    arb = jnp.arange(N, dtype=jnp.int32)

    @functools.partial(jax.jit, static_argnums=(1,))
    def deliver_scan(state, R):
        def body(s, _):
            upd, dropped, injected = mailbox.deliver(
                cfg, s, cand, arb, s.mb_head, s.mb_count)
            return s.replace(**upd), None
        out, _ = jax.lax.scan(body, state, None, length=R)
        return out.metrics.cycles + out.mb_count[0]

    m = marginal(lambda R: deliver_scan(st, R), 64, 448)
    print(f"B. deliver-only marginal: {m:.0f} us/cycle")

    # C: the two-operand sort at candidate size
    keys0 = jnp.asarray(rng.integers(0, 1 << 30, N * S), jnp.int32)
    payload = jnp.asarray(rng.integers(0, 1 << 30, N * S), jnp.int32)

    @functools.partial(jax.jit, static_argnums=(1,))
    def sort_scan(k0, R):
        def body(k, _):
            ks, vs = jax.lax.sort((k, payload), num_keys=1)
            return ks ^ vs, None
        out, _ = jax.lax.scan(body, k0, None, length=R)
        return out[0]

    m = marginal(lambda R: sort_scan(keys0, R), 64, 448)
    print(f"C. sort({N * S} rows) marginal: {m:.0f} us/iter")


if __name__ == "__main__":
    main()
