"""Quickstart: the framework's main surfaces in one script.

Run: python examples/quickstart.py  (CPU; add nothing for the default
device). Each section is independent; see README.md / ARCHITECTURE.md
for the concepts and PERF.md for performance guidance.
"""

import jax

jax.config.update("jax_platforms", "cpu")

from ue22cs343bb1_openmp_assignment_tpu.config import SystemConfig  # noqa: E402
from ue22cs343bb1_openmp_assignment_tpu.models import (  # noqa: E402
    CoherenceSystem, TransactionalSystem)

# -- 1. message-level engine: the reference machine, vectorized ----------
cfg = SystemConfig.reference()          # 4 nodes, 4 lines, 16 blocks
traces = [                              # (op, addr, value): 0=RD, 1=WR
    [(1, 0x15, 100), (0, 0x17, 0)],     # node 0: write remote, read remote
    [(1, 0x05, 200), (0, 0x15, 0)],     # node 1: write remote, read node0's
    [], [],
]
sys_ = CoherenceSystem.from_traces(cfg, traces).run()
print("async engine quiescent:", sys_.quiescent)
print(sys_.dumps()[0][:160], "...\n")   # printProcessorState, byte-exact

# -- 2. transactional engine: atomic rounds at scale ---------------------
# txn_width=3: each node may commit up to 3 coherence transactions per
# round (multi-transaction windows — the throughput default in bench.py)
big = SystemConfig.scale(num_nodes=1024, drain_depth=4, txn_width=3)
tsys = TransactionalSystem.from_workload(
    big, "uniform", trace_len=64, local_frac=0.8).run()
print("sync engine:", tsys.metrics["instrs_retired"], "instrs,",
      tsys.metrics["rounds"], "rounds,",
      tsys.metrics["conflicts"], "conflicts")
tsys.check_invariants()                 # exact-directory invariant

# -- 3. checkpoint / resume / trace streaming ----------------------------
import tempfile                                              # noqa: E402

ckpt_path = tempfile.mktemp(suffix=".ckpt", prefix="quickstart_")
tsys.save(ckpt_path)
restored = TransactionalSystem.load(ckpt_path)
nxt = CoherenceSystem.from_workload(big, "hotspot", trace_len=64).state
phase2 = restored.continue_with(
    instr_arrays=(nxt.instr_op, nxt.instr_addr, nxt.instr_val,
                  nxt.instr_count)).run()
print("streamed 2nd phase:", phase2.metrics["instrs_retired"],
      "instrs total\n")

# -- 4. schedule search: which arbitration seeds reproduce an accepted
#       racy outcome? (the reference needed a sleep-kill-diff retry loop)
import os                                                    # noqa: E402

ref = "/root/reference/tests"
if os.path.isdir(ref):
    from ue22cs343bb1_openmp_assignment_tpu.utils import search  # noqa: E402
    machine = CoherenceSystem.from_test_dir(os.path.join(ref, "test_3"))
    accepted = search.load_accepted(os.path.join(ref, "test_3"))
    matches = search.match_accepted(SystemConfig.reference(),
                                    machine.state, accepted,
                                    seeds=range(8))
    print("test_3 seeds reproducing accepted runs:", matches)

# -- 5. multi-device: shard the node axis over a mesh --------------------
from ue22cs343bb1_openmp_assignment_tpu.parallel import (  # noqa: E402
    make_mesh, make_sharded_round, shard_state)

n_dev = len(jax.devices())
mesh_cfg = SystemConfig.scale(num_nodes=16 * n_dev)
msys = TransactionalSystem.from_workload(mesh_cfg, "uniform",
                                         trace_len=8)
mesh = make_mesh(jax.devices())
sharded = shard_state(mesh_cfg, mesh, msys.state)
stepped = make_sharded_round(mesh_cfg, mesh, sharded)(sharded)
print(f"sharded one round over {n_dev} device(s):",
      int(stepped.round) == 1)
